"""Dispatch-overhead benchmark for the async execution runtime.

Measures steps/sec of a synthetic FAST train step (a tiny FC classifier whose
compiled step costs tens of microseconds, so per-step host work — not kernel
time — dominates) across the three levers this runtime added:

  * divergence guard: off / device-resident with guard_check_every=1 (the old
    react-at-every-batch latency, one host sync per step) / guard_check_every=16
    (bounded-window reaction, one sync per 16 steps);
  * steps_per_dispatch K ∈ {1, 4, 16}: batches fused per compiled lax.scan
    dispatch;
  * checkpointing: synchronous pass-boundary saves on the training thread vs
    the zero-stall async writer (non-blocking D2H fetch + background npz/CRC/
    v1/retention), every pass, keep_last_n=2.

Timing includes the end-of-run checkpoint_wait() flush, so async mode is
charged for its durability barrier. The headline `value` is the speedup of
(guard_check_every=16, K=16, async) over yesterday's defaults
(guard every step, K=1, sync) — the ISSUE 4 acceptance gate is >= 1.3x.

A second, separately-reported pass runs with PADDLE_TPU_TIMER enabled to
split host time across hostFeed / forwardBackward / ckptFetch / ckptWrite.
Enabling timers forces a device sync per dispatch, so that pass measures the
SPLIT, never the throughput.

ISSUE 9 adds a precision × remat grid leg (`precision_remat` in the JSON):
f32/bf16 × none/dots (plus "full" with --full), each entry platform-tagged,
with the compiled step's top-3 HLO cost buckets before (f32/none) and after
(bf16/dots). The heavy version of this drill runs in the nightly pytest tier
(tests/test_precision.py::test_nightly_precision_grid_drill).

Usage:
  JAX_PLATFORMS=cpu python benchmarks/dispatch_bench.py [--batches N]
      [--passes N] [--batch_size N] [--dim N] [--hidden N] [--full]
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def build_trainer(args, guard, precision=None, remat=None):
    from paddle_tpu.nn import costs as C
    from paddle_tpu.nn import layers as L
    from paddle_tpu.nn.graph import reset_name_scope
    from paddle_tpu.optim import SGD
    from paddle_tpu.trainer import SGDTrainer

    reset_name_scope()
    x = L.Data("x", shape=(args.dim,))
    lbl = L.Data("label", shape=())
    logits = L.Fc(L.Fc(x, args.hidden, act="relu"), args.classes, act=None)
    cost = C.ClassificationCost(logits, lbl)
    policy = None if guard == "off" else "skip_batch"
    return SGDTrainer(
        cost, SGD(learning_rate=0.01), seed=0,
        divergence_policy=policy,
        guard_check_every=1 if guard == "off" else int(guard),
        precision=precision, remat=remat,
    )


def make_batches(args):
    import numpy as np

    rs = np.random.RandomState(0)
    return [
        {
            "x": rs.randn(args.batch_size, args.dim).astype(np.float32),
            "label": (np.arange(args.batch_size) % args.classes).astype(
                np.int64
            ),
        }
        for _ in range(args.batches)
    ]


def run_config(args, batches, guard: str, k: int, async_ckpt: bool,
               precision=None, remat=None, cost_report=False) -> dict:
    """steps/sec over the timed passes (pass 0 compiles and is excluded);
    the clock stops only after train() returns, i.e. after the async
    writer's durability barrier. `cost_report=True` attaches the compiled
    step's top-3 HLO cost buckets (obs.profile.trainer_cost_report on the
    trainer this run already built — no rebuild) as `hlo_cost`."""
    from paddle_tpu.trainer import EndPass

    trainer = build_trainer(args, guard, precision=precision, remat=remat)
    save_dir = tempfile.mkdtemp(prefix="dispatch_bench_")
    marks = []

    def handler(e):
        if isinstance(e, EndPass):
            marks.append(time.perf_counter())

    try:
        trainer.train(
            lambda: iter(batches),
            num_passes=1 + args.passes,
            event_handler=handler,
            save_dir=save_dir,
            keep_last_n=2,
            log_period=args.batches // 2 or 1,
            steps_per_dispatch=k,
            async_checkpoint=async_ckpt,
        )
        t_end = time.perf_counter()  # after the checkpoint_wait() barrier
    finally:
        shutil.rmtree(save_dir, ignore_errors=True)
    steps = args.batches * args.passes
    dt = t_end - marks[0]  # timed window starts when the warmup pass ended
    out = {
        "guard": guard,
        "steps_per_dispatch": k,
        "checkpoint": "async" if async_ckpt else "sync",
        "steps_per_sec": round(steps / dt, 1),
        "ms_per_step": round(1e3 * dt / steps, 4),
    }
    if cost_report:
        from paddle_tpu.obs.profile import trainer_cost_report

        try:
            out["hlo_cost"] = trainer_cost_report(
                trainer, batches[0], top_k=3
            )["executables"]["train_step"]
        except Exception as exc:  # noqa: BLE001 — report must not kill bench
            out["hlo_cost"] = {"error": repr(exc)[-200:]}
    return out


def run_timer_split(args, batches) -> dict:
    """One instrumented run of the fully-async config: where host time goes.
    Timers sync per dispatch, so this is diagnostic, not a throughput run."""
    from paddle_tpu.core.stats import GLOBAL_STATS, enable_timers

    GLOBAL_STATS.reset()
    enable_timers(True)
    try:
        run_config(args, batches, guard="16", k=16, async_ckpt=True)
        return {
            name: {"total_ms": round(d["total_ms"], 2), "count": d["count"]}
            for name, d in GLOBAL_STATS.as_dict().items()
        }
    finally:
        enable_timers(False)
        GLOBAL_STATS.reset()


def run_precision_grid(args, batches, full: bool) -> dict:
    """ISSUE 9 grid leg: precision × remat over the same reader, measured
    through the full train loop (run_config), every entry platform-tagged so
    trajectory tooling can exclude CPU rounds per entry (bf16 dots are
    EMULATED on the CPU backend — expect the bf16 legs to lose there; the
    grid exists to show the MXU-path levers and their composition cost).
    `hlo_cost` records the compiled step's top-3 FLOP/byte buckets before
    (f32, no remat) and after (bf16, dots) — the profile-driven-pass
    bookkeeping of ROADMAP item 2."""
    import jax

    platform = jax.default_backend()
    remats = ("none", "dots", "full") if full else ("none", "dots")
    # the before/after of the profile-driven pass: cost reports come off the
    # trainers these two grid legs already built (run_config cost_report=)
    report_legs = {("f32", "none"): "before_f32_none",
                   ("bf16", "dots"): "after_bf16_dots"}
    grid, costs = [], {}
    for precision in ("f32", "bf16"):
        for remat in remats:
            leg = report_legs.get((precision, remat))
            r = run_config(
                args, batches, guard="off", k=1, async_ckpt=True,
                precision=precision, remat=remat, cost_report=bool(leg),
            )
            if leg:
                costs[leg] = r["hlo_cost"]
            grid.append({
                "precision": precision,
                "remat": remat,
                "steps_per_sec": r["steps_per_sec"],
                "ms_per_step": r["ms_per_step"],
                "platform": platform,
            })

    return {"grid": grid, "hlo_cost": costs, "platform": platform}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batches", type=int, default=192, help="batches per pass")
    ap.add_argument("--passes", type=int, default=2, help="timed passes")
    ap.add_argument("--batch_size", type=int, default=64)
    ap.add_argument("--dim", type=int, default=64)
    ap.add_argument("--hidden", type=int, default=64)
    ap.add_argument("--classes", type=int, default=8)
    ap.add_argument(
        "--full", action="store_true",
        help="run the full guard x K x checkpoint grid (18 configs); the "
             "default runs the 8 configs that bracket the answer",
    )
    args = ap.parse_args()

    import jax

    batches = make_batches(args)
    if args.full:
        grid = [
            (g, k, a)
            for g in ("off", "1", "16")
            for k in (1, 4, 16)
            for a in (False, True)
        ]
    else:
        grid = [
            ("1", 1, False),    # yesterday's defaults: per-step sync + sync ckpt
            ("off", 1, False),  # what the guard alone used to cost
            ("16", 1, False),   # device-resident guard, everything else old
            ("1", 16, False),   # fused dispatch, old guard cadence
            ("16", 16, False),  # guard + fusion, sync ckpt
            ("16", 1, True),    # guard + async ckpt, unfused
            ("off", 16, True),  # no guard at all, fully async
            ("16", 16, True),   # the new runtime defaults at K=16
        ]
    results = [run_config(args, batches, g, k, a) for g, k, a in grid]

    def sps(g, k, a):
        for r in results:
            if (
                r["guard"] == g
                and r["steps_per_dispatch"] == k
                and r["checkpoint"] == ("async" if a else "sync")
            ):
                return r["steps_per_sec"]
        return None

    baseline = sps("1", 1, False)
    best = sps("16", 16, True)

    # observability cost check (ISSUE 7 acceptance: disabled tracing must
    # not move steps/sec): re-run the headline config with span recording ON
    # — per-dispatch ring-buffer spans — and report the throughput delta
    from paddle_tpu.obs import trace as obs_trace

    spans0 = obs_trace.TRACER.recorded
    obs_trace.enable_tracing(True)
    try:
        traced = run_config(args, batches, guard="16", k=16, async_ckpt=True)
    finally:
        obs_trace.enable_tracing(False)
    tracing = {
        "config": "guard_check_every=16, K=16, async ckpt, PADDLE_TPU_TRACE=1",
        "steps_per_sec": traced["steps_per_sec"],
        "vs_disabled": (
            round(traced["steps_per_sec"] / best, 4) if best else 0.0
        ),
        "spans_recorded": obs_trace.TRACER.recorded - spans0,
    }

    out = {
        "metric": "dispatch_runtime_speedup",
        "value": round(best / baseline, 3) if baseline and best else 0.0,
        "unit": "x",
        "baseline": {
            "config": "guard_check_every=1, K=1, sync ckpt",
            "steps_per_sec": baseline,
        },
        "async_runtime": {
            "config": "guard_check_every=16, K=16, async ckpt",
            "steps_per_sec": best,
        },
        "grid": results,
        "precision_remat": run_precision_grid(args, batches, args.full),
        "tracing_enabled": tracing,
        "timer_split_instrumented": run_timer_split(args, batches),
        "batches_per_pass": args.batches,
        "timed_passes": args.passes,
        "batch_size": args.batch_size,
        "backend": jax.default_backend(),
    }
    print(json.dumps(out))


if __name__ == "__main__":
    main()
