// Standalone optimizer library — paddle/optimizer parity (SURVEY §2.1:
// C ABI `paddle_create_optimizer` / `paddle_update_parameter`, consumed by
// the Go pserver via cgo; sgd(momentum/nesterov), adagrad, adadelta, adam,
// const/linear lr policies, state (de)serialization).
//
// In the TPU rebuild the compiled train step owns the hot-path updates; this
// library serves the same role as the reference's: an accelerator-free
// optimizer for host-side parameter services (runtime/master-style
// components) with checkpointable state.

#include <cmath>
#include <cstdio>
#include <cstring>
#include <new>
#include <string>
#include <vector>

#include "common.h"

namespace pt {
namespace {

enum OptType { SGD = 0, ADAGRAD = 1, ADADELTA = 2, ADAM = 3 };
enum LrPolicy { LR_CONST = 0, LR_LINEAR = 1 };

struct Optimizer {
  int type = SGD;
  // hyper
  double lr = 0.01, momentum = 0.0, beta1 = 0.9, beta2 = 0.999;
  double epsilon = 1e-8, rho = 0.95, decay = 0.0;
  bool nesterov = false;
  int lr_policy = LR_CONST;
  double lr_decay_a = 0.0, lr_decay_b = 0.0;  // linear: lr - a*steps, floor b
  // state
  int64_t num_updates = 0;
  std::vector<float> m0, m1;  // slot buffers (velocity / moments / accums)

  double current_lr() const {
    if (lr_policy == LR_LINEAR) {
      double v = lr - lr_decay_a * static_cast<double>(num_updates);
      return v > lr_decay_b ? v : lr_decay_b;
    }
    return lr;
  }

  bool needs_slots() const {
    return type != SGD || momentum != 0.0;
  }

  // Returns false when existing slot state is for a DIFFERENT size — a
  // resize would silently zero moments while keeping num_updates (wrong
  // Adam bias correction); callers must match sizes or reset.
  bool ensure(size_t n) {
    if (!needs_slots()) return true;
    if (!m0.empty() && m0.size() != n) return false;
    if (m0.empty()) m0.assign(n, 0.f);
    if ((type == ADADELTA || type == ADAM)) {
      if (!m1.empty() && m1.size() != n) return false;
      if (m1.empty()) m1.assign(n, 0.f);
    }
    return true;
  }

  int update(float* p, const float* g, size_t n) {
    if (!ensure(n)) return -1;
    const double cur_lr = current_lr();
    ++num_updates;
    switch (type) {
      case SGD: {
        if (momentum == 0.0) {
          for (size_t i = 0; i < n; ++i)
            p[i] -= static_cast<float>(cur_lr) * (g[i] + decay * p[i]);
        } else {
          for (size_t i = 0; i < n; ++i) {
            float gi = g[i] + static_cast<float>(decay) * p[i];
            float v = static_cast<float>(momentum) * m0[i] -
                      static_cast<float>(cur_lr) * gi;
            m0[i] = v;
            p[i] += nesterov
                        ? static_cast<float>(momentum) * v -
                              static_cast<float>(cur_lr) * gi
                        : v;
          }
        }
        break;
      }
      case ADAGRAD: {
        for (size_t i = 0; i < n; ++i) {
          m0[i] += g[i] * g[i];
          p[i] -= static_cast<float>(cur_lr) * g[i] /
                  (std::sqrt(m0[i]) + static_cast<float>(epsilon));
        }
        break;
      }
      case ADADELTA: {
        for (size_t i = 0; i < n; ++i) {
          m0[i] = static_cast<float>(rho) * m0[i] +
                  (1.f - static_cast<float>(rho)) * g[i] * g[i];
          float dx = -std::sqrt((m1[i] + static_cast<float>(epsilon)) /
                                (m0[i] + static_cast<float>(epsilon))) *
                     g[i];
          m1[i] = static_cast<float>(rho) * m1[i] +
                  (1.f - static_cast<float>(rho)) * dx * dx;
          p[i] += static_cast<float>(cur_lr) * dx;
        }
        break;
      }
      case ADAM: {
        const double b1p = std::pow(beta1, static_cast<double>(num_updates));
        const double b2p = std::pow(beta2, static_cast<double>(num_updates));
        for (size_t i = 0; i < n; ++i) {
          m0[i] = static_cast<float>(beta1) * m0[i] +
                  (1.f - static_cast<float>(beta1)) * g[i];
          m1[i] = static_cast<float>(beta2) * m1[i] +
                  (1.f - static_cast<float>(beta2)) * g[i] * g[i];
          double mhat = m0[i] / (1.0 - b1p);
          double vhat = m1[i] / (1.0 - b2p);
          p[i] -= static_cast<float>(cur_lr * mhat /
                                     (std::sqrt(vhat) + epsilon));
        }
        break;
      }
    }
    return 0;
  }
};

}  // namespace
}  // namespace pt

using pt::Optimizer;

// type: 0 sgd, 1 adagrad, 2 adadelta, 3 adam
PT_EXPORT void* pt_opt_create(int type, double lr, double momentum,
                              double beta1, double beta2, double epsilon,
                              double rho, double decay, int nesterov) {
  if (type < pt::SGD || type > pt::ADAM) return nullptr;  // unknown type
  auto* o = new (std::nothrow) Optimizer();
  if (!o) return nullptr;
  o->type = type;
  o->lr = lr;
  o->momentum = momentum;
  o->beta1 = beta1;
  o->beta2 = beta2;
  o->epsilon = epsilon;
  o->rho = rho;
  o->decay = decay;
  o->nesterov = nesterov != 0;
  return o;
}

PT_EXPORT void pt_opt_set_lr_policy(void* op, int policy, double decay_a,
                                    double decay_b) {
  auto* o = static_cast<Optimizer*>(op);
  o->lr_policy = policy;
  o->lr_decay_a = decay_a;
  o->lr_decay_b = decay_b;
}

// 0 on success; -1 when existing slot state was created for a different
// parameter size (resize would corrupt Adam bias correction).
PT_EXPORT int pt_opt_update(void* op, float* param, const float* grad,
                            uint64_t n) {
  return static_cast<Optimizer*>(op)->update(param, grad, n);
}

PT_EXPORT double pt_opt_current_lr(void* op) {
  return static_cast<Optimizer*>(op)->current_lr();
}

// Serialization: "PTOS" | version | type | num_updates | slot sizes | slots.
// Returns bytes written (call with buf=null for required size).
PT_EXPORT int64_t pt_opt_serialize(void* op, uint8_t* buf, int64_t cap) {
  auto* o = static_cast<Optimizer*>(op);
  int64_t need = 4 + 4 + 4 + 8 + 8 + 8 +
                 static_cast<int64_t>((o->m0.size() + o->m1.size()) * 4);
  if (!buf) return need;
  if (cap < need) return -1;
  uint8_t* w = buf;
  auto put = [&](const void* src, size_t k) {
    std::memcpy(w, src, k);
    w += k;
  };
  uint32_t magic = 0x50544F53u, version = 1, type = o->type;
  uint64_t n0 = o->m0.size(), n1 = o->m1.size();
  put(&magic, 4);
  put(&version, 4);
  put(&type, 4);
  put(&o->num_updates, 8);
  put(&n0, 8);
  put(&n1, 8);
  if (n0) put(o->m0.data(), n0 * 4);
  if (n1) put(o->m1.data(), n1 * 4);
  return need;
}

PT_EXPORT int pt_opt_deserialize(void* op, const uint8_t* buf, int64_t len) {
  auto* o = static_cast<Optimizer*>(op);
  if (len < 36) return -1;
  const uint8_t* r = buf;
  auto get = [&](void* dst, size_t k) {
    std::memcpy(dst, r, k);
    r += k;
  };
  uint32_t magic, version, type;
  uint64_t n0, n1;
  int64_t num_updates;
  get(&magic, 4);
  get(&version, 4);
  get(&type, 4);
  if (magic != 0x50544F53u || static_cast<int>(type) != o->type) return -1;
  get(&num_updates, 8);
  get(&n0, 8);
  get(&n1, 8);
  // overflow-safe size validation BEFORE any state mutation: each slot count
  // must individually fit in the remaining bytes
  const uint64_t avail = static_cast<uint64_t>(len) - 36;
  if (n0 > avail / 4 || n1 > avail / 4 || (n0 + n1) > avail / 4) return -1;
  std::vector<float> m0(n0), m1(n1);
  if (n0) get(m0.data(), n0 * 4);
  if (n1) get(m1.data(), n1 * 4);
  // commit only after the whole blob parsed
  o->num_updates = num_updates;
  o->m0 = std::move(m0);
  o->m1 = std::move(m1);
  return 0;
}

PT_EXPORT void pt_opt_destroy(void* op) { delete static_cast<Optimizer*>(op); }
