// Shared bits for the paddle-tpu native runtime library.
//
// The TPU compute path is jax/XLA; this library is the native runtime AROUND
// it — host memory pooling, dataset chunk IO, and the elastic task master —
// the pieces the reference implements in C++/Go (paddle/memory buddy
// allocator, Go recordio + go/master task queues; SURVEY §2.1/§2.2).
#pragma once

#include <cstddef>
#include <cstdint>
#include <cstring>

#if defined(_WIN32)
#define PT_EXPORT extern "C" __declspec(dllexport)
#else
#define PT_EXPORT extern "C" __attribute__((visibility("default")))
#endif

namespace pt {

// CRC-32 (IEEE 802.3 polynomial, reflected) — table-driven.
inline uint32_t crc32(const void* data, size_t n, uint32_t seed = 0) {
  static uint32_t table[256];
  static bool init = false;
  if (!init) {
    for (uint32_t i = 0; i < 256; ++i) {
      uint32_t c = i;
      for (int k = 0; k < 8; ++k) c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
      table[i] = c;
    }
    init = true;
  }
  uint32_t c = seed ^ 0xFFFFFFFFu;
  const uint8_t* p = static_cast<const uint8_t*>(data);
  for (size_t i = 0; i < n; ++i) c = table[(c ^ p[i]) & 0xFF] ^ (c >> 8);
  return c ^ 0xFFFFFFFFu;
}

}  // namespace pt
