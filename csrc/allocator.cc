// Buddy allocator over a host arena — paddle/memory parity
// (memory/detail/buddy_allocator.h:33, system_allocator.h:28).
//
// The reference pools cudaMalloc'd device memory; on TPU the device heap is
// XLA's, so the pool serves the host side: pinned staging buffers for feeder
// output, recordio chunk buffers, and prefetch queues. Classic power-of-two
// buddy scheme: one mmap'd arena, split on demand, coalesce on free.

#include <sys/mman.h>

#include <cstdio>
#include <map>
#include <mutex>
#include <new>
#include <vector>

#include "common.h"

namespace pt {
namespace {

struct Pool {
  std::mutex mu;
  uint8_t* arena = nullptr;
  size_t arena_bytes = 0;
  size_t min_order = 0;   // log2 of smallest block
  size_t max_order = 0;   // log2 of arena
  // free_lists[k] holds offsets of free blocks of size 2^(min_order+k)
  std::vector<std::vector<size_t>> free_lists;
  // offset -> order for allocated blocks
  std::map<size_t, size_t> allocated;
  // stats
  uint64_t in_use = 0, peak = 0, n_allocs = 0, n_frees = 0;
};

size_t ceil_log2(size_t n) {
  size_t k = 0;
  while ((size_t(1) << k) < n) ++k;
  return k;
}

}  // namespace
}  // namespace pt

using pt::Pool;

PT_EXPORT void* pt_pool_create(size_t min_block, size_t total_bytes) {
  auto* p = new (std::nothrow) Pool();
  if (!p) return nullptr;
  if (min_block < 64) min_block = 64;
  p->min_order = pt::ceil_log2(min_block);
  p->max_order = pt::ceil_log2(total_bytes);
  if (p->max_order < p->min_order) p->max_order = p->min_order;
  p->arena_bytes = size_t(1) << p->max_order;
  void* mem = mmap(nullptr, p->arena_bytes, PROT_READ | PROT_WRITE,
                   MAP_PRIVATE | MAP_ANONYMOUS, -1, 0);
  if (mem == MAP_FAILED) {
    delete p;
    return nullptr;
  }
  p->arena = static_cast<uint8_t*>(mem);
  p->free_lists.resize(p->max_order - p->min_order + 1);
  p->free_lists.back().push_back(0);  // whole arena free
  return p;
}

PT_EXPORT void* pt_pool_alloc(void* pool, size_t n) {
  auto* p = static_cast<Pool*>(pool);
  if (!p || n == 0) return nullptr;
  size_t order = pt::ceil_log2(n);
  if (order < p->min_order) order = p->min_order;
  if (order > p->max_order) return nullptr;
  size_t k = order - p->min_order;
  std::lock_guard<std::mutex> g(p->mu);
  // find the smallest free block >= requested, splitting down
  size_t j = k;
  while (j < p->free_lists.size() && p->free_lists[j].empty()) ++j;
  if (j >= p->free_lists.size()) return nullptr;  // exhausted
  size_t off = p->free_lists[j].back();
  p->free_lists[j].pop_back();
  while (j > k) {
    --j;
    size_t half = size_t(1) << (p->min_order + j);
    p->free_lists[j].push_back(off + half);  // right buddy stays free
  }
  p->allocated[off] = k;
  p->in_use += size_t(1) << (p->min_order + k);
  if (p->in_use > p->peak) p->peak = p->in_use;
  ++p->n_allocs;
  return p->arena + off;
}

PT_EXPORT int pt_pool_free(void* pool, void* ptr) {
  auto* p = static_cast<Pool*>(pool);
  if (!p || !ptr) return -1;
  size_t off = static_cast<uint8_t*>(ptr) - p->arena;
  std::lock_guard<std::mutex> g(p->mu);
  auto it = p->allocated.find(off);
  if (it == p->allocated.end()) return -1;  // double free / foreign pointer
  size_t k = it->second;
  p->allocated.erase(it);
  p->in_use -= size_t(1) << (p->min_order + k);
  ++p->n_frees;
  // coalesce with buddy while possible
  while (p->min_order + k < p->max_order) {
    size_t size = size_t(1) << (p->min_order + k);
    size_t buddy = off ^ size;
    auto& fl = p->free_lists[k];
    bool merged = false;
    for (size_t i = 0; i < fl.size(); ++i) {
      if (fl[i] == buddy) {
        fl[i] = fl.back();
        fl.pop_back();
        off = off < buddy ? off : buddy;
        ++k;
        merged = true;
        break;
      }
    }
    if (!merged) break;
  }
  p->free_lists[k].push_back(off);
  return 0;
}

// out[0..4] = arena_bytes, in_use, peak, n_allocs, n_frees
PT_EXPORT void pt_pool_stats(void* pool, uint64_t* out) {
  auto* p = static_cast<Pool*>(pool);
  std::lock_guard<std::mutex> g(p->mu);
  out[0] = p->arena_bytes;
  out[1] = p->in_use;
  out[2] = p->peak;
  out[3] = p->n_allocs;
  out[4] = p->n_frees;
}

PT_EXPORT void pt_pool_destroy(void* pool) {
  auto* p = static_cast<Pool*>(pool);
  if (!p) return;
  munmap(p->arena, p->arena_bytes);
  delete p;
}
