// RecordIO-style chunked record file — parity with the Go recordio library
// the reference's master/pserver data path shards datasets into
// (SURVEY §2.2 go/master "chunks of RecordIO"; python v2/dataset `convert`).
//
// On-disk layout (little-endian), one file = N chunks:
//   chunk := magic:u32 | num_records:u32 | data_len:u32 | crc32(data):u32
//            | data (records back to back)
//   record := len:u32 | bytes
//
// Corrupt chunks are detected by CRC and skipped record-exactly (the reader
// reports them via pt_recordio_errors), which is what makes chunk-granular
// task re-dispatch safe in the elastic master.

#include <cstdio>
#include <new>
#include <string>
#include <vector>

#include "common.h"

namespace pt {
namespace {

constexpr uint32_t kMagic = 0x50545243u;  // "PTRC"
// Shared format limit (writers enforce, readers treat violations as
// corruption; mirrored by _PyWriter/_py_read in runtime/recordio.py). Keeps a
// corrupted data_len from driving a multi-GiB allocation whose bad_alloc
// would escape the extern-C ABI.
constexpr uint32_t kMaxChunkBytes = 1u << 30;  // 1 GiB

struct Writer {
  FILE* f = nullptr;
  std::vector<std::string> pending;
  size_t pending_bytes = 0;
  int chunk_records;
  size_t chunk_bytes;

  int flush() {
    if (pending.empty()) return 0;
    std::string data;
    data.reserve(pending_bytes + 4 * pending.size());
    for (auto& r : pending) {
      uint32_t len = static_cast<uint32_t>(r.size());
      data.append(reinterpret_cast<const char*>(&len), 4);
      data.append(r);
    }
    uint32_t head[4] = {kMagic, static_cast<uint32_t>(pending.size()),
                        static_cast<uint32_t>(data.size()),
                        crc32(data.data(), data.size())};
    if (fwrite(head, sizeof(head), 1, f) != 1) return -1;
    if (!data.empty() && fwrite(data.data(), data.size(), 1, f) != 1) return -1;
    pending.clear();
    pending_bytes = 0;
    return 0;
  }
};

struct Reader {
  FILE* f = nullptr;
  std::vector<std::string> chunk;  // decoded records of current chunk
  size_t next = 0;                 // next record index in chunk
  uint64_t bad_chunks = 0;

  // loads the next valid chunk; false on EOF
  bool load_chunk() {
    for (;;) {
      uint32_t head[4];
      if (fread(head, sizeof(head), 1, f) != 1) return false;  // EOF
      if (head[0] != kMagic) {
        // stream corrupt beyond chunk framing: stop rather than scan
        ++bad_chunks;
        return false;
      }
      // A data_len beyond the format limit (which writers enforce) is
      // corruption — never a legitimate chunk.
      if (head[2] > kMaxChunkBytes) {
        ++bad_chunks;
        return false;  // framing untrustworthy: stop rather than scan
      }
      std::string data(head[2], '\0');
      if (head[2] && fread(&data[0], head[2], 1, f) != 1) {
        ++bad_chunks;
        return false;  // truncated tail
      }
      if (crc32(data.data(), data.size()) != head[3]) {
        ++bad_chunks;
        continue;  // skip corrupt chunk, try next
      }
      chunk.clear();
      size_t off = 0;
      bool ok = true;
      for (uint32_t i = 0; i < head[1]; ++i) {
        if (off + 4 > data.size()) { ok = false; break; }
        uint32_t len;
        std::memcpy(&len, data.data() + off, 4);
        off += 4;
        if (off + len > data.size()) { ok = false; break; }
        chunk.emplace_back(data.data() + off, len);
        off += len;
      }
      if (!ok) {
        ++bad_chunks;
        continue;
      }
      next = 0;
      if (!chunk.empty()) return true;
    }
  }
};

}  // namespace
}  // namespace pt

using pt::Reader;
using pt::Writer;

PT_EXPORT void* pt_recordio_writer_open(const char* path, int chunk_records,
                                        size_t chunk_bytes) {
  auto* w = new (std::nothrow) Writer();
  if (!w) return nullptr;
  w->f = fopen(path, "wb");
  if (!w->f) {
    delete w;
    return nullptr;
  }
  w->chunk_records = chunk_records > 0 ? chunk_records : 1000;
  w->chunk_bytes = chunk_bytes > 0 ? chunk_bytes : (8u << 20);
  return w;
}

PT_EXPORT int pt_recordio_write(void* wp, const void* buf, uint64_t len) {
  auto* w = static_cast<Writer*>(wp);
  // reject records the format cannot represent in a readable chunk
  if (len + 4 > pt::kMaxChunkBytes) return -2;
  w->pending.emplace_back(static_cast<const char*>(buf), len);
  w->pending_bytes += len;
  if (w->pending.size() >= static_cast<size_t>(w->chunk_records) ||
      w->pending_bytes >= w->chunk_bytes)
    return w->flush();
  return 0;
}

PT_EXPORT int pt_recordio_writer_close(void* wp) {
  auto* w = static_cast<Writer*>(wp);
  int rc = w->flush();
  if (fclose(w->f) != 0) rc = -1;
  delete w;
  return rc;
}

PT_EXPORT void* pt_recordio_reader_open(const char* path) {
  auto* r = new (std::nothrow) Reader();
  if (!r) return nullptr;
  r->f = fopen(path, "rb");
  if (!r->f) {
    delete r;
    return nullptr;
  }
  return r;
}

// Returns record length and sets *out to an internal buffer valid until the
// next call; -1 on EOF.
PT_EXPORT int64_t pt_recordio_next(void* rp, const void** out) {
  auto* r = static_cast<Reader*>(rp);
  if (r->next >= r->chunk.size() && !r->load_chunk()) return -1;
  const std::string& rec = r->chunk[r->next++];
  *out = rec.data();
  return static_cast<int64_t>(rec.size());
}

PT_EXPORT uint64_t pt_recordio_errors(void* rp) {
  return static_cast<Reader*>(rp)->bad_chunks;
}

PT_EXPORT void pt_recordio_reader_close(void* rp) {
  auto* r = static_cast<Reader*>(rp);
  fclose(r->f);
  delete r;
}
