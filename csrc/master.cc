// Elastic task master — go/master/service.go parity (SURVEY §2.2):
// fault-tolerant dataset-task dispatch with todo/pending/done queues, task
// timeouts + re-queue, failure caps, pass bookkeeping, and state snapshots.
//
// The Go reference keys recovery off etcd; here snapshots go to a local file
// (multi-host deployments put it on shared storage) and service discovery is
// jax.distributed's coordinator. Trainers stay stateless task consumers:
// GetTask / TaskFinished / TaskFailed, exactly the reference RPC surface
// (service.go:368/:411/:455).

#include <chrono>
#include <cstdio>
#include <deque>
#include <map>
#include <mutex>
#include <new>
#include <string>
#include <vector>

#include "common.h"

namespace pt {
namespace {

double now_s() {
  using namespace std::chrono;
  return duration<double>(steady_clock::now().time_since_epoch()).count();
}

struct Task {
  int64_t id = 0;
  std::string payload;  // chunk path list, newline-joined
  int failures = 0;
  double deadline = 0;  // pending only
};

struct Master {
  std::mutex mu;
  double timeout_s;
  int failure_max;
  int64_t next_id = 0;
  int pass = 0;
  std::deque<Task> todo;
  std::map<int64_t, Task> pending;
  std::vector<Task> done;
  std::vector<Task> discarded;  // failed > failure_max
  std::vector<std::string> dataset;  // payloads, kept to refill next pass

  void requeue_timeouts() {
    double t = now_s();
    for (auto it = pending.begin(); it != pending.end();) {
      if (it->second.deadline <= t) {
        Task task = it->second;
        it = pending.erase(it);
        fail_one(std::move(task));
      } else {
        ++it;
      }
    }
  }

  void fail_one(Task task) {
    if (++task.failures > failure_max)
      discarded.push_back(std::move(task));
    else
      todo.push_back(std::move(task));
  }

  void start_pass() {
    todo.clear();
    pending.clear();
    done.clear();
    discarded.clear();
    for (auto& p : dataset) {
      Task t;
      t.id = next_id++;
      t.payload = p;
      todo.push_back(std::move(t));
    }
  }
};

}  // namespace
}  // namespace pt

using pt::Master;
using pt::Task;

PT_EXPORT void* pt_master_create(double timeout_s, int failure_max) {
  auto* m = new (std::nothrow) Master();
  if (!m) return nullptr;
  m->timeout_s = timeout_s > 0 ? timeout_s : 60.0;
  m->failure_max = failure_max > 0 ? failure_max : 3;
  return m;
}

// payloads: n NUL-terminated strings concatenated; each becomes one task
// (the caller groups chunk paths into per-task payloads — chunks_per_task
// grouping happens in the Python layer that lists the recordio files).
PT_EXPORT void pt_master_set_dataset(void* mp, const char* payloads, int n) {
  auto* m = static_cast<Master*>(mp);
  std::lock_guard<std::mutex> g(m->mu);
  m->dataset.clear();
  const char* p = payloads;
  for (int i = 0; i < n; ++i) {
    m->dataset.emplace_back(p);
    p += m->dataset.back().size() + 1;
  }
  m->pass = 0;
  m->start_pass();
}

// Returns task id >= 0 and copies payload into buf (cap bytes incl. NUL);
// -1: no task available now (all pending — caller retries);
// -2: pass finished (todo+pending empty); -3: buffer too small.
PT_EXPORT int64_t pt_master_get_task(void* mp, char* buf, int64_t cap) {
  auto* m = static_cast<Master*>(mp);
  std::lock_guard<std::mutex> g(m->mu);
  m->requeue_timeouts();
  if (m->todo.empty()) return m->pending.empty() ? -2 : -1;
  Task t = std::move(m->todo.front());
  m->todo.pop_front();
  if (static_cast<int64_t>(t.payload.size()) + 1 > cap) {
    m->todo.push_front(std::move(t));
    return -3;
  }
  std::memcpy(buf, t.payload.c_str(), t.payload.size() + 1);
  t.deadline = pt::now_s() + m->timeout_s;
  int64_t id = t.id;
  m->pending[id] = std::move(t);
  return id;
}

PT_EXPORT int pt_master_task_finished(void* mp, int64_t id) {
  auto* m = static_cast<Master*>(mp);
  std::lock_guard<std::mutex> g(m->mu);
  auto it = m->pending.find(id);
  if (it == m->pending.end()) return -1;  // unknown/timed-out → already requeued
  m->done.push_back(std::move(it->second));
  m->pending.erase(it);
  return 0;
}

PT_EXPORT int pt_master_task_failed(void* mp, int64_t id) {
  auto* m = static_cast<Master*>(mp);
  std::lock_guard<std::mutex> g(m->mu);
  auto it = m->pending.find(id);
  if (it == m->pending.end()) return -1;
  Task t = std::move(it->second);
  m->pending.erase(it);
  m->fail_one(std::move(t));
  return 0;
}

// 1 if the pass is finished (everything done or discarded), else 0.
// next_pass=1 also refills the todo queue for the next pass.
PT_EXPORT int pt_master_pass_finished(void* mp, int next_pass) {
  auto* m = static_cast<Master*>(mp);
  std::lock_guard<std::mutex> g(m->mu);
  m->requeue_timeouts();
  if (!m->todo.empty() || !m->pending.empty()) return 0;
  if (next_pass) {
    ++m->pass;
    m->start_pass();
  }
  return 1;
}

// stats: out[0]=todo out[1]=pending out[2]=done out[3]=discarded out[4]=pass
PT_EXPORT void pt_master_stats(void* mp, int64_t* out) {
  auto* m = static_cast<Master*>(mp);
  std::lock_guard<std::mutex> g(m->mu);
  out[0] = static_cast<int64_t>(m->todo.size());
  out[1] = static_cast<int64_t>(m->pending.size());
  out[2] = static_cast<int64_t>(m->done.size());
  out[3] = static_cast<int64_t>(m->discarded.size());
  out[4] = m->pass;
}

// Snapshot format: "PTMS" | version | pass | next_id | section counts |
// length-prefixed payload+failures per task. Pending tasks snapshot as todo
// (on recovery they are re-dispatched — exactly the Go master's behavior of
// re-queuing leases that out-lived the process, service.go:166).
PT_EXPORT int pt_master_snapshot(void* mp, const char* path) {
  auto* m = static_cast<Master*>(mp);
  std::lock_guard<std::mutex> g(m->mu);
  FILE* f = fopen(path, "wb");
  if (!f) return -1;
  auto w32 = [&](uint32_t v) { return fwrite(&v, 4, 1, f) == 1; };
  auto w64 = [&](int64_t v) { return fwrite(&v, 8, 1, f) == 1; };
  auto wtask = [&](const Task& t) {
    uint32_t len = static_cast<uint32_t>(t.payload.size());
    return w64(t.id) && w32(len) && w32(static_cast<uint32_t>(t.failures)) &&
           (len == 0 || fwrite(t.payload.data(), len, 1, f) == 1);
  };
  bool ok = w32(0x50544D53u) && w32(1) && w32(m->pass) && w64(m->next_id);
  ok = ok && w32(static_cast<uint32_t>(m->todo.size() + m->pending.size()));
  ok = ok && w32(static_cast<uint32_t>(m->done.size()));
  ok = ok && w32(static_cast<uint32_t>(m->dataset.size()));
  for (auto& t : m->todo) ok = ok && wtask(t);
  for (auto& kv : m->pending) ok = ok && wtask(kv.second);
  for (auto& t : m->done) ok = ok && wtask(t);
  for (auto& p : m->dataset) {
    uint32_t len = static_cast<uint32_t>(p.size());
    ok = ok && w32(len) && (len == 0 || fwrite(p.data(), len, 1, f) == 1);
  }
  if (fclose(f) != 0) ok = false;
  return ok ? 0 : -1;
}

PT_EXPORT int pt_master_restore(void* mp, const char* path) {
  auto* m = static_cast<Master*>(mp);
  std::lock_guard<std::mutex> g(m->mu);
  FILE* f = fopen(path, "rb");
  if (!f) return -1;
  auto r32 = [&](uint32_t* v) { return fread(v, 4, 1, f) == 1; };
  auto r64 = [&](int64_t* v) { return fread(v, 8, 1, f) == 1; };
  // corrupt length fields must not drive multi-GiB allocations: bad_alloc
  // would escape the extern-C ABI and abort (same class recordio.cc caps)
  constexpr uint32_t kMaxBlob = 64u << 20;  // 64 MiB per payload/path
  auto rtask = [&](Task* t) {
    uint32_t len, fails;
    if (!r64(&t->id) || !r32(&len) || !r32(&fails)) return false;
    if (len > kMaxBlob) return false;
    t->failures = static_cast<int>(fails);
    t->payload.resize(len);
    return len == 0 || fread(&t->payload[0], len, 1, f) == 1;
  };
  uint32_t magic, version, pass, n_todo, n_done, n_data;
  int64_t next_id;
  bool ok = r32(&magic) && magic == 0x50544D53u && r32(&version) &&
            r32(&pass) && r64(&next_id) && r32(&n_todo) && r32(&n_done) &&
            r32(&n_data);
  // parse into locals and commit only after the whole file read cleanly —
  // a truncated/corrupt snapshot must leave the in-memory queues untouched
  // (same commit-after-parse shape as pt_opt_deserialize)
  std::deque<Task> todo;
  std::vector<Task> done;
  std::vector<std::string> dataset;
  if (ok) {
    for (uint32_t i = 0; ok && i < n_todo; ++i) {
      Task t;
      ok = rtask(&t);
      if (ok) todo.push_back(std::move(t));
    }
    for (uint32_t i = 0; ok && i < n_done; ++i) {
      Task t;
      ok = rtask(&t);
      if (ok) done.push_back(std::move(t));
    }
    for (uint32_t i = 0; ok && i < n_data; ++i) {
      uint32_t len;
      ok = r32(&len) && len <= kMaxBlob;
      if (!ok) break;
      std::string p(len, '\0');
      if (len) ok = fread(&p[0], len, 1, f) == 1;
      if (ok) dataset.push_back(std::move(p));
    }
  }
  fclose(f);
  if (!ok) return -1;
  m->todo = std::move(todo);
  m->pending.clear();
  m->done = std::move(done);
  m->discarded.clear();
  m->dataset = std::move(dataset);
  m->pass = static_cast<int>(pass);
  m->next_id = next_id;
  return 0;
}

PT_EXPORT void pt_master_destroy(void* mp) { delete static_cast<Master*>(mp); }
